"""Benchmark: TPU-native metrics vs reference TorchMetrics (torch CPU).

Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "hardware": ...,
   "configs": {...}}``

Headline = config #1 (per-step stateful update+compute — the apples-to-apples hot
loop: our jit-cached dispatch vs the reference's eager per-step update). The
``configs`` dict carries every BASELINE.md config measured this run, each with its own
``vs_baseline`` (``null`` where the reference cannot run in this image).

The line also carries an ``obs`` key: telemetry from a scripted 3-metric
instrumented mini-run (jit cache hits/misses + compile spans, per-collective
sync timings against a faked 2-host world, robust update counters) exercising
the ``torchmetrics_tpu.obs`` egress. ``TM_TPU_BENCH_OBS=1`` additionally runs
each config with tracing enabled and attaches per-config summaries — such a
round's timings include the tracing overhead and are not comparable with
untraced rounds (hence off by default).

Every run also appends its per-config results to ``BENCH_HISTORY.jsonl`` (atomic
append via the obs regression sentinel); ``python bench.py --check-regressions``
additionally judges the fresh run against that history with noise-aware
tolerances and exits 1 on a breach (see ``torchmetrics_tpu/obs/regress.py``).
A ``memory`` key (``peak_rss_bytes``, and ``device_peak_bytes_in_use`` when the
backend reports memory stats) rides in the JSON line and the history record as
recorded-but-never-judged fields, so memory trends accumulate without gating.

``python bench.py --chaos`` runs the OTHER bench: the traffic-replay chaos
scenario (``torchmetrics_tpu/chaos/``) — a seeded multi-tenant schedule with
poisoned batches and a hung host, replayed through tenant pipeline sessions
while the obs server is scraped concurrently, judged against declarative SLOs
(throughput, p95/p99 scrape latency, time-to-fire/resolve, compiled-variant
churn, flight-dump correctness) and recorded in the same history with
``kind: "slo"`` configs the regression sentinel gates. Exits non-zero on an
outright SLO failure, or (with ``--check-regressions``) on a history breach.

Backend policy: the host pins ``JAX_PLATFORMS=axon`` (tunneled TPU) and the tunnel has
been wedged at bench time in past rounds. We probe the backend *in a subprocess* (a
wedged tunnel hangs forever, it doesn't error), retry with backoff at bench time, and
only then fall back to an 8-device virtual CPU mesh tagged ``cpu-fallback``.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

BATCH = 4096
NUM_CLASSES = 100
STEPS = 120

# every run's per-config results append here (one JSON line per run); the
# regression sentinel (torchmetrics_tpu.obs.regress) judges the newest run
# against this history — `python bench.py --check-regressions` gates on it
_HISTORY_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_HISTORY.jsonl")


# --------------------------------------------------------------------------- backend


def _probe_once(timeout_s: int = 75):
    probe = "import jax; d = jax.devices(); print(d[0].platform)"
    try:
        out = subprocess.run(
            [sys.executable, "-c", probe], capture_output=True, text=True, timeout=timeout_s
        )
        if out.returncode == 0 and out.stdout.strip():
            return out.stdout.strip().splitlines()[-1]
    except subprocess.TimeoutExpired:
        pass
    return None


def _acquire_backend() -> str:
    """Probe the pinned backend with retry+backoff *now* (bench time), then fall back.

    Round-1/2 postmortem: a single early probe that never re-checks turned one transient
    tunnel outage into a whole round of CPU numbers. Three probes spread over ~3 minutes
    is cheap insurance against a relay that is restarting.
    """
    for wait in (0, 30, 60):
        if wait:
            time.sleep(wait)
        platform = _probe_once()
        if platform:
            return platform
    # JAX is deliberately NOT initialised in the main process on fallback — the
    # worker subprocesses each pin their own device count (1 vs 8)
    return "cpu-fallback"


# ------------------------------------------------------------------- reference setup


def _install_lightning_utilities_stub() -> None:
    """Minimal in-memory stand-in for the reference's `lightning_utilities` dependency
    (not installed in this image) so the baseline can be measured."""
    import importlib
    import importlib.util
    import types
    from enum import Enum

    if "lightning_utilities" in sys.modules:
        return

    def package_available(name: str) -> bool:
        try:
            return importlib.util.find_spec(name) is not None
        except Exception:
            return False

    class RequirementCache:
        def __init__(self, requirement: str = "", module: str = None) -> None:
            self.requirement = requirement
            self.module = module

        def __bool__(self) -> bool:
            name = self.module or self.requirement.split(">")[0].split("<")[0].split("=")[0].strip()
            try:
                importlib.import_module(name)
                return True
            except Exception:
                return False

        def __str__(self) -> str:
            return self.requirement

    class StrEnum(str, Enum):
        @classmethod
        def from_str(cls, value, source="key"):
            for member in cls:
                if member.value.lower() == str(value).lower().replace("-", "_"):
                    return member
            raise ValueError(f"Invalid value {value!r} for {cls.__name__}")

    def apply_to_collection(data, dtype, function, *args, **kwargs):
        if isinstance(data, dtype):
            return function(data, *args, **kwargs)
        if isinstance(data, dict):
            return {k: apply_to_collection(v, dtype, function, *args, **kwargs) for k, v in data.items()}
        if isinstance(data, (list, tuple)):
            return type(data)(apply_to_collection(v, dtype, function, *args, **kwargs) for v in data)
        return data

    root = types.ModuleType("lightning_utilities")
    core = types.ModuleType("lightning_utilities.core")
    imports_mod = types.ModuleType("lightning_utilities.core.imports")
    enums_mod = types.ModuleType("lightning_utilities.core.enums")
    apply_mod = types.ModuleType("lightning_utilities.core.apply_func")
    imports_mod.package_available = package_available
    imports_mod.RequirementCache = RequirementCache
    imports_mod.compare_version = lambda *a, **k: True
    enums_mod.StrEnum = StrEnum
    apply_mod.apply_to_collection = apply_to_collection
    root.apply_to_collection = apply_to_collection
    root.core = core
    core.imports = imports_mod
    core.enums = enums_mod
    core.apply_func = apply_mod
    sys.modules["lightning_utilities"] = root
    sys.modules["lightning_utilities.core"] = core
    sys.modules["lightning_utilities.core.imports"] = imports_mod
    sys.modules["lightning_utilities.core.enums"] = enums_mod
    sys.modules["lightning_utilities.core.apply_func"] = apply_mod


def _reference_modules():
    """Import the reference TorchMetrics from /root/reference (torch CPU)."""
    _install_lightning_utilities_stub()
    if "/root/reference/src" not in sys.path:
        sys.path.insert(0, "/root/reference/src")
    import torchmetrics  # noqa: F401

    return torchmetrics


# ------------------------------------------------------------------------ our configs


def _stage_data():
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    preds = jnp.asarray(rng.rand(STEPS, BATCH, NUM_CLASSES).astype(np.float32))
    target = jnp.asarray(rng.randint(0, NUM_CLASSES, (STEPS, BATCH)))
    return preds, target


def bench_acc_stateful(preds, target) -> float:
    """Config #1: per-step stateful ``metric.update`` loop + one ``compute``.

    This is the same call pattern a user writes and the same pattern the reference
    baseline runs eagerly: one update per step, jit-cached dispatch per call.
    """
    import jax

    from torchmetrics_tpu.classification import MulticlassAccuracy

    metric = MulticlassAccuracy(num_classes=NUM_CLASSES, average="micro", validate_args=False)
    # pre-split batches: slicing the stacked stream inside the loop would charge a
    # per-step device copy that the eager reference baseline never pays
    n_distinct = 8
    batches = [(preds[i], target[i]) for i in range(n_distinct)]
    jax.block_until_ready(batches)
    metric.update(*batches[0])
    jax.block_until_ready(metric.compute())
    metric.reset()

    start = time.perf_counter()
    for i in range(STEPS):
        p, t = batches[i % n_distinct]
        metric.update(p, t)
    jax.block_until_ready(metric.compute())
    elapsed = time.perf_counter() - start
    return elapsed / STEPS * 1e6


def bench_acc_engine(preds, target, fuse: int):
    """Engine configs: the config #1 hot loop driven through the streaming engine.

    ``fuse=1`` is the pipelined per-batch path (prefetch + bounded async window,
    one dispatch per step — measures the engine's overhead over the bare loop);
    ``fuse=8`` fuses 8 batches per ``lax.scan`` dispatch. Both AOT-warmup first
    (``MetricPipeline.warmup``), so the timed region contains zero XLA compiles.
    Returns ``(us_per_step, stats)`` where ``stats`` carries the timed run's
    dispatch accounting plus warmup/persistent-compile-cache totals — recorded
    in the bench JSON and history lines, never judged by the regression gate.
    """
    import jax

    from torchmetrics_tpu.classification import MulticlassAccuracy
    from torchmetrics_tpu.engine import MetricPipeline, PipelineConfig, persistent_cache_stats

    metric = MulticlassAccuracy(num_classes=NUM_CLASSES, average="micro", validate_args=False)
    pipe = MetricPipeline(metric, PipelineConfig(fuse=fuse, max_in_flight=4, prefetch=2))
    n_distinct = 8
    batches = [(preds[i], target[i]) for i in range(n_distinct)]
    jax.block_until_ready(batches)
    cache_before = persistent_cache_stats()
    manifest = pipe.warmup(*batches[0])
    pipe.run(batches)  # warm run: every remaining dispatch path executes once
    jax.block_until_ready(metric.compute())
    metric.reset()

    before = pipe.report().asdict()
    start = time.perf_counter()
    pipe.run(batches[i % n_distinct] for i in range(STEPS))
    jax.block_until_ready(metric.compute())
    elapsed = time.perf_counter() - start
    after = pipe.close().asdict()
    cache_after = persistent_cache_stats()
    timed = {
        key: after[key] - before[key]
        for key in after
        if isinstance(after[key], int) and isinstance(before.get(key), int)
        and key not in ("max_chunk", "last_chunk")  # gauges, not counters: diffing lies
    }
    timed["max_chunk"] = after["max_chunk"]
    timed["dispatches_per_batch"] = (
        round(timed["host_dispatches"] / timed["batches"], 4) if timed.get("batches") else None
    )
    stats = {
        "fuse": fuse,
        "timed_run": timed,
        "warmup": {
            "variants": manifest["variants"],
            "fresh_compiles": manifest["fresh_compiles"],
            "total_compile_seconds": manifest["total_compile_seconds"],
            "cache_dir": manifest["cache_dir"],
        },
        "compile_cache": {
            "entries": cache_after["entries"],
            "hits": cache_after["hits"] - cache_before["hits"],
            "requests": cache_after["requests"] - cache_before["requests"],
        },
    }
    return elapsed / STEPS * 1e6, stats


def bench_acc_mux(preds, target, n_tenants: int):
    """Multiplexer configs: N tenant sessions through ONE cross-tenant fused
    dispatch stream vs N per-tenant pipeline sessions (the PR-8 serving shape).

    Both sides drive the same sliced accuracy batches (256 rows — the tenant
    axis, not the per-tenant batch, is the load), both are warmed outside the
    timed region (AOT for the mux, a discarded warm round for the pipelines),
    and both close over the same total tenant-update count. Returns
    ``(mux_us_per_update, stats)`` where ``stats`` carries the per-tenant
    baseline timing, the speedup, and — the structural claim — each side's
    fresh-compiled-variant count from the cost ledger: the baseline compiles
    O(tenants) programs (every instance its own jit cache), the mux
    O(width-buckets).
    """
    import jax

    from torchmetrics_tpu.classification import MulticlassAccuracy
    from torchmetrics_tpu.engine import (
        MetricPipeline,
        MuxConfig,
        PipelineConfig,
        TenantMultiplexer,
    )
    from torchmetrics_tpu.obs import cost as _cost_mod

    rows = 256  # per-tenant batch rows: small on purpose (the tenant axis is the load)
    n_distinct = 8
    batches = [(preds[i][:rows], target[i][:rows]) for i in range(n_distinct)]
    jax.block_until_ready(batches)
    rounds = max(2, 256 // n_tenants)
    total = rounds * n_tenants
    make = lambda: MulticlassAccuracy(  # noqa: E731 - bench-local factory
        num_classes=NUM_CLASSES, average="micro", validate_args=False
    )
    ledger = _cost_mod.get_ledger()

    # ---- fused multiplexer: one dispatch folds up to n_tenants rows
    mux_mark = ledger.mark()
    mux = TenantMultiplexer(make, MuxConfig(max_width=n_tenants))
    tenants = [f"mux{n_tenants}-{i:03d}" for i in range(n_tenants)]
    for t in tenants:
        mux.adopt(t)
    mux.warmup(*batches[0])
    for t in tenants:  # warm round: remaining dispatch paths execute once
        mux.feed(t, *batches[0])
    mux.flush()
    for t in tenants:
        jax.block_until_ready(mux.compute(t))
        mux.metric(t).reset()
    before = mux.report().asdict()
    start = time.perf_counter()
    for r in range(rounds):
        for j, t in enumerate(tenants):
            mux.feed(t, *batches[(r + j) % n_distinct])
    mux.flush()
    # drain EVERY tenant's async state before stopping the clock — blocking
    # on one tenant would leave in-flight work outside the timed region
    jax.block_until_ready([mux.metric(t)._state_values for t in tenants])
    mux_elapsed = time.perf_counter() - start
    after = mux.close().asdict()
    mux_variants = ledger.since(mux_mark)["variants_compiled"]
    mux_us = mux_elapsed / total * 1e6

    # ---- baseline: one pipeline session per tenant (fuse=1: the serving
    # shape before cross-tenant batching — per-tenant dispatch streams)
    base_mark = ledger.mark()
    pipes = {
        t: MetricPipeline(
            make(), PipelineConfig(fuse=1, max_in_flight=2, prefetch=0, tenant=f"pipe-{t}")
        )
        for t in tenants
    }
    for t, pipe in pipes.items():  # warm round (each instance compiles its own program)
        pipe.feed(*batches[0])
        jax.block_until_ready(pipe.compute())
        pipe.metric.reset()
    start = time.perf_counter()
    for r in range(rounds):
        for j, (t, pipe) in enumerate(pipes.items()):
            pipe.feed(*batches[(r + j) % n_distinct])
    for pipe in pipes.values():
        pipe.flush()
    # symmetric drain: all N independent pipelines' async dispatches must
    # finish inside the timed region, exactly as on the mux side
    jax.block_until_ready([pipe.metric._state_values for pipe in pipes.values()])
    base_elapsed = time.perf_counter() - start
    for pipe in pipes.values():
        pipe.close()
    base_variants = ledger.since(base_mark)["variants_compiled"]
    base_us = base_elapsed / total * 1e6

    timed = {
        key: after[key] - before[key]
        for key in after
        if isinstance(after[key], int) and isinstance(before.get(key), int)
        and key not in ("max_width", "last_width")
    }
    stats = {
        "tenants": n_tenants,
        "rows_per_batch": rows,
        "updates_timed": total,
        "mux_us_per_update": round(mux_us, 3),
        "per_tenant_pipelines_us_per_update": round(base_us, 3),
        "speedup_vs_per_tenant": round(base_us / mux_us, 3) if mux_us > 0 else None,
        "timed_run": timed,
        "compiled_variants": {"mux": mux_variants, "per_tenant_pipelines": base_variants},
        "cache": mux.cache_info(),
    }
    return mux_us, stats


def bench_acc_scan(preds, target) -> float:
    """Config #2: whole epoch folded through ``lax.scan`` in ONE XLA program."""
    import jax

    from torchmetrics_tpu.classification import MulticlassAccuracy

    metric = MulticlassAccuracy(num_classes=NUM_CLASSES, average="micro", validate_args=False)

    @jax.jit
    def run_epoch(state, preds, target):
        state = metric.scan_update(state, preds, target)
        return metric.pure_compute(state), state

    value, _ = run_epoch(metric.init_state(), preds, target)
    jax.block_until_ready(value)

    reps = 2
    start = time.perf_counter()
    for _ in range(reps):
        value, _ = run_epoch(metric.init_state(), preds, target)
        jax.block_until_ready(value)
    elapsed = time.perf_counter() - start
    return elapsed / (STEPS * reps) * 1e6


def _build_collection_step(sync: bool, n_dev: int):
    """Build (jitted step fn, initial states, preds, target) for the collection config.

    Jitted shard_map step over ``n_dev`` devices: per-shard pure updates of the two
    compute groups (stat-scores shared by Acc/F1; binned-curve for AUROC) + psum
    sync — the production distributed pattern. ``sync=False`` is the identical step
    with the collectives removed.
    """
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from torchmetrics_tpu.classification import MulticlassAccuracy, MulticlassAUROC, MulticlassF1Score

    n_classes = 10
    devices = np.array(jax.devices()[:n_dev])
    mesh = Mesh(devices, ("data",))
    per_step = 1024 * n_dev

    rng = np.random.RandomState(0)
    preds = jnp.asarray(rng.rand(per_step, n_classes).astype(np.float32))
    target = jnp.asarray(rng.randint(0, n_classes, (per_step,)))

    acc = MulticlassAccuracy(num_classes=n_classes, average="macro", validate_args=False)
    f1 = MulticlassF1Score(num_classes=n_classes, average="macro", validate_args=False)
    auroc = MulticlassAUROC(num_classes=n_classes, thresholds=100, validate_args=False)

    def step(states, p, t):
        s_stat, s_curve = states
        # Acc and F1 share one stat-scores state (what MetricCollection's compute
        # groups dedup to); AUROC keeps the binned-curve state.
        s_stat = acc.pure_update(s_stat, p, t)
        s_curve = auroc.pure_update(s_curve, p, t)
        if sync:
            sy_stat = acc.sync_state(s_stat, axis_name="data")
            sy_curve = auroc.sync_state(s_curve, axis_name="data")
        else:
            sy_stat, sy_curve = s_stat, s_curve
        vals = (acc.pure_compute(sy_stat), f1.pure_compute(sy_stat), auroc.pure_compute(sy_curve))
        return (s_stat, s_curve), vals

    f = jax.jit(
        shard_map(
            step,
            mesh=mesh,
            in_specs=((P(), P()), P("data"), P("data")),
            out_specs=((P(), P()), (P(), P(), P())),
            check_vma=False,
        )
    )
    states = (acc.init_state(), auroc.init_state())
    return f, states, preds, target


def _time_collection_step(f, states, preds, target, iters: int = 30) -> float:
    import jax

    states, vals = f(states, preds, target)  # warmup (compile)
    jax.block_until_ready(vals)
    start = time.perf_counter()
    for _ in range(iters):
        states, vals = f(states, preds, target)
    jax.block_until_ready(vals)
    return (time.perf_counter() - start) / iters * 1e6


def bench_collection_mesh_sync(sync: bool = True) -> float:
    """Config #3: Accuracy+F1+AUROC update & mesh sync per step (BASELINE.md config 2).

    The reference baseline runs the same three metrics eagerly WITHOUT any sync (its
    DDP needs a process group we can't spawn here), so its number is a lower bound
    for the reference.
    """
    import jax

    f, states, preds, target = _build_collection_step(sync, len(jax.devices()))
    return _time_collection_step(f, states, preds, target)


def bench_sync_overhead_stats(reps: int = 5) -> dict:
    """Statistically bounded sync-overhead claim (round-4 verdict weak item 2).

    One pair of compiled steps (with/without collectives) per device count; then
    ``reps`` *interleaved* timed rounds on the full mesh so both sides see the same
    host drift. Reports the median with-sync/without-sync step times, the per-round
    overhead percentages' median and min-max spread, and a device-scaling curve
    (2/4/8-device overhead) when the mesh has that many devices.
    """
    import jax

    n_dev = len(jax.devices())
    built = {s: _build_collection_step(s, n_dev) for s in (True, False)}
    t_sync, t_nosync = [], []
    for _ in range(reps):
        t_sync.append(_time_collection_step(*built[True]))
        t_nosync.append(_time_collection_step(*built[False]))
    overheads = [max(0.0, (s - n) / s * 100.0) for s, n in zip(t_sync, t_nosync) if s > 0]

    curve = {}
    for nd in (2, 4, 8):
        if nd <= n_dev and nd != n_dev:
            pair = {s: _build_collection_step(s, nd) for s in (True, False)}
            ts = _time_collection_step(*pair[True])
            tn = _time_collection_step(*pair[False])
            if ts > 0:
                curve[str(nd)] = round(max(0.0, (ts - tn) / ts * 100.0), 2)
    if overheads:
        curve[str(n_dev)] = round(float(np.median(overheads)), 2)

    return {
        "collection": float(np.median(t_sync)),
        "collection_nosync": float(np.median(t_nosync)),
        "sync_overhead_pct_median": round(float(np.median(overheads)), 2) if overheads else None,
        "sync_overhead_pct_min": round(min(overheads), 2) if overheads else None,
        "sync_overhead_pct_max": round(max(overheads), 2) if overheads else None,
        "sync_overhead_reps": len(overheads),
        "sync_overhead_curve": curve,
    }


def bench_pr_curve() -> float:
    """Config #5-ish: binned multiclass PR-curve, 50 update steps + compute (ms total)."""
    import jax

    from torchmetrics_tpu.classification import MulticlassPrecisionRecallCurve

    import jax.numpy as jnp

    n_classes = 10
    steps = 50
    rng = np.random.RandomState(0)
    preds = jnp.asarray(rng.rand(steps, BATCH, n_classes).astype(np.float32))
    target = jnp.asarray(rng.randint(0, n_classes, (steps, BATCH)))

    metric = MulticlassPrecisionRecallCurve(num_classes=n_classes, thresholds=200, validate_args=False)

    @jax.jit
    def run(state, preds, target):
        state = metric.scan_update(state, preds, target)
        return metric.pure_compute(state)

    out = run(metric.init_state(), preds, target)
    jax.block_until_ready(out)
    start = time.perf_counter()
    jax.block_until_ready(run(metric.init_state(), preds, target))
    return (time.perf_counter() - start) * 1e3


def bench_inception(hardware: str) -> float:
    """Config #4: FID-path Inception-v3 feature extraction throughput (imgs/sec).

    Random weights — identical FLOPs/layout to the pretrained net, so imgs/sec is
    representative even though scores would not be. Smaller batch on the CPU fallback
    so the config is never silently skipped.
    """
    import warnings

    import jax.numpy as jnp

    from torchmetrics_tpu.image._inception_net import InceptionFeatureExtractor

    on_cpu = hardware.startswith("cpu")
    batch, iters = (8, 2) if on_cpu else (64, 5)

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        ext = InceptionFeatureExtractor(feature=2048)
    imgs = jnp.zeros((batch, 3, 299, 299), dtype=jnp.uint8)
    ext(imgs).block_until_ready()  # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        out = ext(imgs)
    out.block_until_ready()
    return batch * iters / (time.perf_counter() - t0)


# --------------------------------------------------- model-based + text configs

_WORDS = (
    "the cat sat on mat quick brown fox jumps over lazy dog model metric stream "
    "update compute shard mesh chip fast slow image text score batch epoch"
).split()


def _corpus(n: int, seed: int = 0, length: int = 16):
    rng = np.random.RandomState(seed)
    return [" ".join(rng.choice(_WORDS, length)) for _ in range(n)]


def _fabricate_clip_dir(root: str, tiny: bool) -> str:
    """Random-weight local CLIP snapshot: tiny dims on the CPU fallback (the same
    fabrication the multimodal tests use), real ViT-B/32 dims on TPU — FLOPs match
    the pretrained model, so samples/sec is representative even though scores are not.
    """
    import json as _json

    from transformers import (
        CLIPConfig,
        CLIPImageProcessor,
        CLIPProcessor,
        CLIPTextConfig,
        CLIPTokenizer,
        CLIPVisionConfig,
        FlaxCLIPModel,
    )

    os.makedirs(root, exist_ok=True)
    chars = "abcdefghijklmnopqrstuvwxyz0123456789"
    vocab = {}
    for c in chars:
        vocab[c] = len(vocab)
    for c in chars:
        vocab[c + "</w>"] = len(vocab)
    vocab["<|startoftext|>"] = len(vocab)
    vocab["<|endoftext|>"] = len(vocab)
    with open(root + "/vocab.json", "w") as fh:
        _json.dump(vocab, fh)
    with open(root + "/merges.txt", "w") as fh:
        fh.write("#version: 0.2\n")
    tokenizer = CLIPTokenizer(root + "/vocab.json", root + "/merges.txt")

    if tiny:
        text_cfg = CLIPTextConfig(
            vocab_size=tokenizer.vocab_size, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=37, max_position_embeddings=77,
        )
        vision_cfg = CLIPVisionConfig(
            hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
            intermediate_size=37, image_size=30, patch_size=6,
        )
        proj, img_size = 16, 30
    else:  # openai/clip-vit-base-patch32 dims
        text_cfg = CLIPTextConfig(
            vocab_size=tokenizer.vocab_size, hidden_size=512, num_hidden_layers=12,
            num_attention_heads=8, intermediate_size=2048, max_position_embeddings=77,
        )
        vision_cfg = CLIPVisionConfig(
            hidden_size=768, num_hidden_layers=12, num_attention_heads=12,
            intermediate_size=3072, image_size=224, patch_size=32,
        )
        proj, img_size = 512, 224
    config = CLIPConfig(
        text_config=text_cfg.to_dict(), vision_config=vision_cfg.to_dict(), projection_dim=proj
    )
    FlaxCLIPModel(config).save_pretrained(root)
    image_processor = CLIPImageProcessor(
        size={"shortest_edge": img_size}, crop_size={"height": img_size, "width": img_size}
    )
    CLIPProcessor(image_processor=image_processor, tokenizer=tokenizer).save_pretrained(root)
    return root


def _fabricate_bert_dir(root: str, tiny: bool) -> str:
    """Random-weight local BERT snapshot + wordpiece tokenizer over the bench corpus.

    Encoder dims are BERT-base on TPU (the FLOPs that matter for BERTScore — no vocab
    softmax in the scoring path), tiny on the CPU fallback.
    """
    from transformers import BertConfig, BertTokenizerFast, FlaxBertModel

    os.makedirs(root, exist_ok=True)
    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"] + sorted(set(_WORDS))
    with open(root + "/vocab.txt", "w") as fh:
        fh.write("\n".join(vocab))
    if tiny:
        config = BertConfig(
            vocab_size=len(vocab), hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=64, max_position_embeddings=64,
        )
    else:  # bert-base encoder dims
        config = BertConfig(
            vocab_size=len(vocab), hidden_size=768, num_hidden_layers=12,
            num_attention_heads=12, intermediate_size=3072, max_position_embeddings=512,
        )
    FlaxBertModel(config).save_pretrained(root)
    BertTokenizerFast(vocab_file=root + "/vocab.txt", do_lower_case=True).save_pretrained(root)
    return root


def bench_clip_score(hardware: str) -> float:
    """BASELINE.md config 4: CLIPScore samples/sec (ViT-B/32-dims random weights on
    TPU, tiny fabricated model on the CPU fallback)."""
    import tempfile
    import warnings

    import jax
    import jax.numpy as jnp

    from torchmetrics_tpu.multimodal import CLIPScore

    tiny = hardware.startswith("cpu")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        d = _fabricate_clip_dir(tempfile.mkdtemp(prefix="bench_clip_"), tiny)
        metric = CLIPScore(model_name_or_path=d)
    n, iters, size = (4, 2, 30) if tiny else (32, 5, 224)
    rng = np.random.RandomState(0)
    imgs = jnp.asarray(rng.randint(0, 256, (n, 3, size, size), dtype=np.uint8))
    texts = _corpus(n, seed=1, length=6)
    # epoch pattern: N updates accumulate (scores are scalar sums), one compute
    metric.update(imgs, texts)  # compile + processor warmup
    jax.block_until_ready(metric.compute())
    metric.reset()
    start = time.perf_counter()
    for _ in range(iters):
        metric.update(imgs, texts)
    np.asarray(metric.compute())
    return n * iters / (time.perf_counter() - start)


def bench_bert_score(hardware: str) -> float:
    """BASELINE.md config 5a: BERTScore sentence-pairs/sec (BERT-base encoder dims
    random weights on TPU, tiny on the CPU fallback)."""
    import tempfile
    import warnings

    import jax

    from torchmetrics_tpu.text import BERTScore

    tiny = hardware.startswith("cpu")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        d = _fabricate_bert_dir(tempfile.mkdtemp(prefix="bench_bert_"), tiny)
        metric = BERTScore(model_name_or_path=d, num_layers=None)
    n, iters = (32, 3) if tiny else (64, 5)
    preds = _corpus(n, seed=2, length=12)
    target = _corpus(n, seed=3, length=12)
    # epoch pattern: N updates accumulate, one compute (BERTScore re-embeds the
    # accumulated corpus at compute — same contract as the reference module)
    metric.update(preds, target)
    np.asarray(metric.compute()["f1"])
    metric.reset()
    start = time.perf_counter()
    for _ in range(iters):
        metric.update(preds, target)
    np.asarray(metric.compute()["f1"])
    return n * iters / (time.perf_counter() - start)


_PPL_SHAPE = (8, 128, 8192)  # batch, seq, vocab — same logits both sides


def bench_perplexity() -> float:
    """BASELINE.md config 5b: Perplexity sequences/sec over (8, 128, 8192) logits —
    the metric side of the LM-eval loop, honest same-shape differential vs the
    reference (the model forward producing logits is benched separately)."""
    import jax
    import jax.numpy as jnp

    from torchmetrics_tpu.text import Perplexity

    b, t, v = _PPL_SHAPE
    rng = np.random.RandomState(0)
    preds = jnp.asarray(rng.rand(b, t, v).astype(np.float32))
    target = jnp.asarray(rng.randint(0, v, (b, t)))
    metric = Perplexity()
    steps = 10
    metric.update(preds, target)
    jax.block_until_ready(metric.compute())
    metric.reset()
    start = time.perf_counter()
    for _ in range(steps):
        metric.update(preds, target)
    jax.block_until_ready(metric.compute())
    return b * steps / (time.perf_counter() - start)


_ROUGE_N = 64


def bench_rouge() -> float:
    """BASELINE.md config 5c: ROUGE-1/2/L samples/sec over a seeded corpus — honest
    differential (pure text metric, no weights on either side)."""
    from torchmetrics_tpu.functional.text.rouge import rouge_score

    keys = ("rouge1", "rouge2", "rougeL")  # rougeLsum needs the nltk punkt download
    preds = _corpus(_ROUGE_N, seed=4, length=20)
    target = _corpus(_ROUGE_N, seed=5, length=20)
    rouge_score(preds, target, rouge_keys=keys)  # warm caches
    iters = 3
    start = time.perf_counter()
    for _ in range(iters):
        rouge_score(preds, target, rouge_keys=keys)
    return _ROUGE_N * iters / (time.perf_counter() - start)


# -------------------------------------------------------- pallas A/B hot-op configs


def bench_hotops() -> dict:
    """Kernel-backed hot ops, ms each — run twice (TM_TPU_USE_PALLAS=0/1 subprocess
    env) on real TPU hardware so the Pallas kernels get an automatic A/B the moment
    the relay yields a chip. Op set mirrors the kernel surface: confmat, binned
    curve, bincount, SSIM moments."""
    import jax
    import jax.numpy as jnp

    from torchmetrics_tpu.functional.classification.confusion_matrix import multiclass_confusion_matrix
    from torchmetrics_tpu.functional.classification.precision_recall_curve import (
        multiclass_precision_recall_curve,
    )
    from torchmetrics_tpu.functional.image.ssim import structural_similarity_index_measure
    from torchmetrics_tpu.utils.data import _bincount

    rng = np.random.RandomState(0)
    out = {}

    def timeit(fn, *args, iters=5):
        jax.block_until_ready(fn(*args))
        start = time.perf_counter()
        for _ in range(iters):
            val = fn(*args)
        jax.block_until_ready(val)
        return (time.perf_counter() - start) / iters * 1e3

    n, c = 1 << 18, 512
    preds_l = jnp.asarray(rng.randint(0, c, n))
    target_l = jnp.asarray(rng.randint(0, c, n))
    out["confmat_262k_c512_ms"] = _safe(
        timeit, lambda p, t: multiclass_confusion_matrix(p, t, c, validate_args=False), preds_l, target_l
    )

    scores = jnp.asarray(rng.rand(1 << 18, 16).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, 16, 1 << 18))
    out["binned_curve_262k_c16_t200_ms"] = _safe(
        timeit,
        lambda p, t: multiclass_precision_recall_curve(p, t, 16, thresholds=200, validate_args=False),
        scores, labels,
    )

    vals = jnp.asarray(rng.randint(0, 4096, 1 << 20))
    out["bincount_1m_c4096_ms"] = _safe(
        timeit, lambda x: _bincount(x, minlength=4096), vals
    )

    img1 = jnp.asarray(rng.rand(4, 3, 256, 256).astype(np.float32))
    img2 = jnp.asarray(rng.rand(4, 3, 256, 256).astype(np.float32))
    out["ssim_4x3x256_ms"] = _safe(
        timeit, lambda a, b: structural_similarity_index_measure(a, b, data_range=1.0), img1, img2
    )

    from torchmetrics_tpu.functional.classification.calibration_error import (
        binary_calibration_error,
    )

    conf = jnp.asarray(rng.rand(1 << 20).astype(np.float32))
    lbls = jnp.asarray(rng.randint(0, 2, 1 << 20))
    out["calibration_1m_b100_ms"] = _safe(
        timeit, lambda p, t: binary_calibration_error(p, t, n_bins=100), conf, lbls
    )
    return out


# ------------------------------------------------------------------ reference configs


def ref_acc_stateful() -> float:
    import torch

    from torchmetrics.classification import MulticlassAccuracy as TMAcc

    rng = np.random.RandomState(0)
    preds = torch.from_numpy(rng.rand(BATCH, NUM_CLASSES).astype(np.float32))
    target = torch.from_numpy(rng.randint(0, NUM_CLASSES, (BATCH,)))
    metric = TMAcc(num_classes=NUM_CLASSES, average="micro", validate_args=False)
    for _ in range(10):
        metric.update(preds, target)
    metric.compute()
    metric.reset()
    start = time.perf_counter()
    for _ in range(STEPS):
        metric.update(preds, target)
    metric.compute()
    return (time.perf_counter() - start) / STEPS * 1e6


def ref_collection() -> float:
    import torch

    from torchmetrics import MetricCollection
    from torchmetrics.classification import MulticlassAccuracy, MulticlassAUROC, MulticlassF1Score

    n_classes = 10
    n_dev = 8  # match the per-step element count of our mesh config
    per_step = 1024 * n_dev
    rng = np.random.RandomState(0)
    preds = torch.from_numpy(rng.rand(per_step, n_classes).astype(np.float32))
    target = torch.from_numpy(rng.randint(0, n_classes, (per_step,)))
    col = MetricCollection([
        MulticlassAccuracy(num_classes=n_classes, average="macro", validate_args=False),
        MulticlassF1Score(num_classes=n_classes, average="macro", validate_args=False),
        MulticlassAUROC(num_classes=n_classes, thresholds=100, validate_args=False),
    ])
    for _ in range(3):
        col.update(preds, target)
    col.compute()
    col.reset()
    iters = 50
    start = time.perf_counter()
    for _ in range(iters):
        col.update(preds, target)
        col.compute()
    return (time.perf_counter() - start) / iters * 1e6


def ref_perplexity() -> float:
    import torch

    from torchmetrics.text import Perplexity as TMPerplexity

    b, t, v = _PPL_SHAPE
    rng = np.random.RandomState(0)
    preds = torch.from_numpy(rng.rand(b, t, v).astype(np.float32))
    target = torch.from_numpy(rng.randint(0, v, (b, t)))
    metric = TMPerplexity()
    steps = 10
    metric.update(preds, target)
    metric.compute()
    metric.reset()
    start = time.perf_counter()
    for _ in range(steps):
        metric.update(preds, target)
    metric.compute()
    return b * steps / (time.perf_counter() - start)


def ref_rouge() -> float:
    from torchmetrics.functional.text.rouge import rouge_score as tm_rouge

    keys = ("rouge1", "rouge2", "rougeL")
    preds = _corpus(_ROUGE_N, seed=4, length=20)
    target = _corpus(_ROUGE_N, seed=5, length=20)
    tm_rouge(preds, target, rouge_keys=keys)
    iters = 3
    start = time.perf_counter()
    for _ in range(iters):
        tm_rouge(preds, target, rouge_keys=keys)
    return _ROUGE_N * iters / (time.perf_counter() - start)


def ref_pr_curve() -> float:
    import torch

    from torchmetrics.classification import MulticlassPrecisionRecallCurve as TMCurve

    n_classes = 10
    steps = 50
    rng = np.random.RandomState(0)
    preds = torch.from_numpy(rng.rand(steps, BATCH, n_classes).astype(np.float32))
    target = torch.from_numpy(rng.randint(0, n_classes, (steps, BATCH)))
    metric = TMCurve(num_classes=n_classes, thresholds=200, validate_args=False)
    metric.update(preds[0], target[0])
    metric.compute()
    metric.reset()
    start = time.perf_counter()
    for i in range(steps):
        metric.update(preds[i], target[i])
    metric.compute()
    return (time.perf_counter() - start) * 1e3


# ------------------------------------------------------------------------ chaos


def _checkpoint_overhead_probe(batches: int = 64, cadence: int = 4) -> dict:
    """Checkpoint-cadence overhead: the same stream with the policy on vs off.

    A small fused pipeline folds ``batches`` identical batches twice — once
    plain, once with a ``CheckpointPolicy(every_batches=cadence)`` writing
    delta bundles to a tempdir — and the per-batch wall ratio is recorded.
    Rides the bench line's top-level ``checkpoint`` key through
    ``obs.regress.run_record`` recorded-but-never-judged (the ``memory``
    passthrough pattern), so the cadence tax accumulates as a trend without
    gating anything; PERF.md carries the methodology.
    """
    import shutil
    import tempfile
    import time as _time

    import jax.numpy as jnp
    import numpy as np

    from torchmetrics_tpu.classification import MulticlassAccuracy
    from torchmetrics_tpu.engine import CheckpointPolicy, MetricPipeline, PipelineConfig

    rng = np.random.RandomState(0)
    data = [
        (
            jnp.asarray(rng.rand(32, 4).astype(np.float32)),
            jnp.asarray(rng.randint(0, 4, 32)),
        )
        for _ in range(batches)
    ]

    def run(policy):
        metric = MulticlassAccuracy(num_classes=4, average="micro", validate_args=False)
        pipe = MetricPipeline(metric, PipelineConfig(fuse=2, checkpoint=policy))
        pipe.warmup(*data[0])
        start = _time.perf_counter()
        for b in data:
            pipe.feed(*b)
        pipe.flush()
        import jax

        jax.block_until_ready(metric._state_values)
        return _time.perf_counter() - start, pipe._checkpointer

    off_seconds, _ = run(None)
    ckpt_dir = tempfile.mkdtemp(prefix="tm_tpu_ckpt_probe_")
    try:
        on_seconds, checkpointer = run(
            CheckpointPolicy(directory=ckpt_dir, every_batches=cadence, full_every=4, keep=4)
        )
        stats = checkpointer.stats
        out = {
            "batches": batches,
            "cadence_batches": cadence,
            "off_us_per_batch": round(off_seconds / batches * 1e6, 3),
            "on_us_per_batch": round(on_seconds / batches * 1e6, 3),
            "overhead_ratio": round(on_seconds / off_seconds, 4) if off_seconds > 0 else None,
            "bundles_full": stats["full"]["count"],
            "bundles_delta": stats["delta"]["count"],
            "bundle_bytes_full": stats["full"]["bytes"],
            "bundle_bytes_delta": stats["delta"]["bytes"],
        }
    finally:
        shutil.rmtree(ckpt_dir, ignore_errors=True)
    return out


def _chaos_main(argv) -> None:
    """``python bench.py --chaos``: the traffic-replay chaos bench.

    Generates (or loads) a seeded deterministic schedule, replays it through
    per-tenant pipeline sessions under concurrent obs-server scrape, judges
    the SLOs (torchmetrics_tpu/chaos/), prints ONE JSON line, appends the run
    to BENCH_HISTORY.jsonl (configs carry ``kind: "slo"`` so the regression
    sentinel judges them), and exits non-zero when an SLO fails outright —
    or, with ``--check-regressions``, when a judged number regresses past its
    noise-aware tolerance. The SLO table goes to stderr (the one-JSON-line
    stdout contract holds).
    """
    import argparse

    parser = argparse.ArgumentParser(prog="python bench.py --chaos")
    parser.add_argument("--chaos", action="store_true", help=argparse.SUPPRESS)
    parser.add_argument("--check-regressions", action="store_true")
    parser.add_argument("--chaos-tenants", type=int, default=8)
    parser.add_argument("--chaos-seed", type=int, default=0)
    parser.add_argument(
        "--chaos-scenario",
        choices=(
            "default",
            "high_tenant",
            "rolling_deploy",
            "host_crash",
            "hung_host",
            "skewed_load",
            "flash_crowd",
        ),
        default="default",
        help="high_tenant: >=64 tenants with shared signatures and bursty arrivals,"
             " replayed through the cross-tenant multiplexer and judged against the"
             " high-tenant SLO spec (configs prefixed chaos_ht_*)."
             " rolling_deploy: one 'host' is killed mid-traffic and its tenant"
             " sessions migrate to the survivor via the live-session"
             " checkpoint/restore protocol, judged against the rolling-deploy SLO"
             " spec incl. bit-identity vs unmigrated controls (configs prefixed"
             " chaos_rd_*)."
             " host_crash: one 'host' dies UNPLANNED (SIGKILL semantics, no drain)"
             " mid-traffic; its sessions ran continuous periodic delta bundles"
             " (engine/migrate.py CheckpointPolicy) and are recovered from the"
             " newest intact bundle with the replay gap re-fed from the"
             " deterministic schedule, judged against the host-crash SLO spec"
             " incl. gap<=cadence, bit-identity vs unkilled controls and"
             " delta-vs-full bundle bytes (configs prefixed chaos_hc_*)."
             " hung_host: one 'host' WEDGES mid-traffic (alive but silent: no"
             " drain, no close, no lease release); the scrape-driven lease"
             " watchdog fences its tenant sessions and fails them over to the"
             " survivor under a new epoch, judged against the hung-host SLO"
             " spec incl. time-to-detect/time-to-failover budgets, zombie"
             " bundle-write rejection and bit-identity vs never-hung controls"
             " (configs prefixed chaos_hh_*)."
             " skewed_load: a static placement concentrates every tenant but"
             " one onto one virtual host; the fleet telemetry plane"
             " (obs/fleet.py — continuous sampling, rate derivation, skew"
             " signals, GET /fleet) must page on the imbalance within budget"
             " from fleet samples alone, track a mid-run hot-spot shift, and"
             " degrade loudly when a gather wedges, judged against the"
             " skewed-load SLO spec (configs prefixed chaos_sk_*)."
             " flash_crowd: the whole crowd lands on one of two provisioned"
             " virtual hosts (two tenants running hot at a heavy factor, a"
             " mid-run hot-spot shift); the placement controller"
             " (torchmetrics_tpu/fleet/) must fix the measured skew with real"
             " drain/checkpoint/restore session moves and re-converge after"
             " the shift; a static-placement control arm replays the same"
             " schedule first for the throughput-ratio floor; judged against"
             " the flash-crowd SLO spec incl. convergence budget, zero-loss"
             " bit-identity vs unmoved controls, durable table restore and"
             " GET /placement service (configs prefixed chaos_fc_*)",
    )
    parser.add_argument(
        "--chaos-schedule", default=None,
        help="replay a recorded schedule JSONL instead of generating one",
    )
    parser.add_argument(
        "--chaos-save-schedule", default=None,
        help="also record the (generated) schedule JSONL here (atomic write)",
    )
    parser.add_argument(
        "--chaos-report", default=None,
        help="write the full SLO report JSON here (atomic write; the CI artifact)",
    )
    parser.add_argument(
        "--chaos-flamegraph", default=None,
        help="write the host profiler's collapsed-stack flamegraph file here"
             " (flamegraph.pl input; only written when the scenario ran with"
             " the profiler live, e.g. high_tenant)",
    )
    parser.add_argument(
        "--chaos-trace", default=None,
        help="write one stitched GET /trace/<id> JSON (an injected-NaN batch's full"
             " lineage story) here — the batch-lineage CI artifact",
    )
    args = parser.parse_args(argv)

    # fast backend choice (the chaos loop runs in THIS process): honor an
    # explicit CPU pin, else one bounded probe of the pinned backend, else the
    # shared force-cpu recipe — never the full 3-probe bench backoff, and
    # never a first-touch init that can hang on a wedged tunnel
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        hardware = "cpu-fallback"
    else:
        platform = _probe_once(timeout_s=45)
        if platform is None or platform.startswith("cpu"):
            from _jax_cpu_force import force_cpu

            force_cpu(1)
            hardware = "cpu-fallback"
        else:
            hardware = platform

    from torchmetrics_tpu import chaos
    from torchmetrics_tpu.utils.fileio import atomic_write_text

    high_tenant = args.chaos_scenario == "high_tenant"
    if args.chaos_schedule:
        sched = chaos.load(args.chaos_schedule)
    elif high_tenant:
        sched = chaos.generate(
            chaos.high_tenant_config(seed=args.chaos_seed, tenants=max(64, args.chaos_tenants))
        )
    elif args.chaos_scenario == "skewed_load":
        sched = chaos.generate(
            chaos.skewed_load_config(seed=args.chaos_seed, tenants=max(4, args.chaos_tenants))
        )
    elif args.chaos_scenario == "flash_crowd":
        sched = chaos.generate(
            chaos.flash_crowd_config(seed=args.chaos_seed, tenants=max(12, args.chaos_tenants))
        )
    else:
        sched = chaos.generate(
            chaos.ScheduleConfig(seed=args.chaos_seed, tenants=args.chaos_tenants)
        )
    if args.chaos_save_schedule:
        sched.save(args.chaos_save_schedule)

    if high_tenant:
        # the multiplexed scenario: guarded/hung tenants share ONE cross-tenant
        # fused dispatch stream; distinct config prefix so the sentinel never
        # baselines this workload against the default scenario's
        result = chaos.replay(
            sched, chaos.ReplayConfig(multiplex=True, mux_max_width=len(sched.tenants))
        )
        report = chaos.judge(result, chaos.high_tenant_slo_spec(), prefix="chaos_ht")
    elif args.chaos_scenario == "rolling_deploy":
        # the live-migration scenario: host B is killed mid-traffic, its tenant
        # sessions drain→checkpoint→restore→replay-tail onto the survivor with
        # shadow controls proving bit-identity; own prefix, own baselines
        result = chaos.replay(sched, chaos.ReplayConfig(rolling_deploy=True))
        report = chaos.judge(result, chaos.rolling_deploy_slo_spec(), prefix="chaos_rd")
    elif args.chaos_scenario == "host_crash":
        # the crash-consistency scenario: host B dies with SIGKILL semantics
        # (no drain, no final checkpoint); recovery restores from the last
        # continuous periodic bundle and re-feeds the bounded replay gap
        result = chaos.replay(sched, chaos.ReplayConfig(host_crash=True))
        report = chaos.judge(result, chaos.host_crash_slo_spec(), prefix="chaos_hc")
    elif args.chaos_scenario == "hung_host":
        # the fencing scenario: host B wedges (hung, not dead) mid-traffic;
        # the lease watchdog — ticked by the /metrics scrape loop — detects
        # the stale lease, fences the zombie epoch and restores the tenants
        # elsewhere under a new epoch; the zombie's late bundle write must
        # land fenced-out and be discarded by the next recovery scan
        result = chaos.replay(sched, chaos.ReplayConfig(hung_host=True))
        report = chaos.judge(result, chaos.hung_host_slo_spec(), prefix="chaos_hh")
    elif args.chaos_scenario == "skewed_load":
        # the fleet-telemetry scenario: a static placement makes one virtual
        # host hot; the installed FleetSampler — ticked by the /metrics scrape
        # loop — must derive rates + skew from merged host snapshots, page on
        # sustained imbalance through the standard alert machinery, follow the
        # mid-run hot-spot shift, and degrade loudly when a gather wedges
        result = chaos.replay(sched, chaos.ReplayConfig(skewed_load=True))
        report = chaos.judge(result, chaos.skewed_load_slo_spec(), prefix="chaos_sk")
    elif args.chaos_scenario == "flash_crowd":
        # the placement-control-plane scenario: every tenant lands on virtual
        # host "0" under a LIVE PlacementController — reconcile ticks ride the
        # /metrics scrape loop, moves are real drain→checkpoint→restore
        # handoffs, and the hot spot shifts mid-run. The control arm replays
        # the IDENTICAL schedule with the controller off first: the
        # throughput-ratio floor proves the controller does not cost
        # meaningful throughput (same-host virtual moves cannot prove it
        # adds any — see PERF.md)
        control = chaos.replay(
            sched, chaos.ReplayConfig(flash_crowd=True, placement_enabled=False)
        )
        result = chaos.replay(sched, chaos.ReplayConfig(flash_crowd=True))
        if result.get("placement") is not None:
            result["placement"]["control_arm_updates_per_second"] = control.get(
                "updates_per_second"
            )
            # the full sample the judge needs to compare both arms net of
            # their own measured compile wall and scheduled idle (each arm
            # pays a different compile bill: moves mint fresh programs)
            result["placement"]["control_arm"] = {
                "batches_fed": control.get("batches_fed"),
                "wall_seconds": control.get("wall_seconds"),
                "sleep_seconds": control.get("sleep_seconds"),
                "compile_seconds": (control.get("cost") or {}).get(
                    "compile_seconds"
                ),
                "updates_per_second": control.get("updates_per_second"),
            }
        report = chaos.judge(result, chaos.flash_crowd_slo_spec(), prefix="chaos_fc")
    else:
        result = chaos.replay(sched)
        report = chaos.judge(result)
    sys.stderr.write(chaos.format_report(report))

    line = {
        "metric": (
            f"chaos replay bench ({args.chaos_scenario} scenario, {len(sched.tenants)} tenants,"
            f" {result['batches_fed']} batches, seed {sched.config.seed})"
        ),
        "value": 1.0 if report["passed"] else 0.0,
        "unit": "slo_pass",
        "vs_baseline": None,
        "hardware": hardware,
        "configs": report["configs"],
        "slo": {k: report[k] for k in ("passed", "n_slos", "failed")},
        "chaos": {
            "schedule": result["schedule"],
            "wall_seconds": result["wall_seconds"],
            # driver-side (client-observed) scrape summaries only: the server
            # histograms carry +Inf bucket bounds that are not strict JSON —
            # the full detail lands in --chaos-report, judged numbers in configs
            "scrapes": {
                "driver": result["scrapes"]["driver"],
                "degraded_healthz_seen": result["scrapes"]["degraded_healthz_seen"],
            },
            "faults": result["faults"],
            "robust": result["robust"],
            "cost": result["cost"],
            "scenario": args.chaos_scenario,
            # cross-tenant fused dispatch accounting (None when unmultiplexed)
            "mux": result["mux"],
            # live-migration accounting (None unless rolling_deploy)
            "migration": result.get("migration"),
            # crash-recovery accounting (None unless host_crash)
            "crash": result.get("crash"),
            # hung-host fencing accounting (None unless hung_host)
            "fence": result.get("fence"),
            # fleet-telemetry accounting (None unless skewed_load/flash_crowd)
            "fleet": result.get("fleet"),
            # placement-control-plane accounting (None unless flash_crowd);
            # the bulky decision log + /placement probe payload stay out of
            # the history line — the full detail lands in --chaos-report
            "placement": (
                {
                    key: value
                    for key, value in result["placement"].items()
                    if key not in ("report", "probe")
                }
                if isinstance(result.get("placement"), dict)
                else None
            ),
            # batch-lineage causality rows (trace id → dump/alert links)
            "lineage_poisoned": (result.get("lineage") or {}).get("poisoned"),
        },
    }
    if result.get("lineage"):
        # trace-index cardinality rides the history recorded-never-judged
        # (the `memory` passthrough pattern): size/minted/evicted trends
        # accumulate across rounds without gating anything
        line["lineage"] = {"index": result["lineage"]["index"]}
    if isinstance(result.get("hostprof"), dict):
        # the host profiler's attribution trend rides the history the same
        # recorded-never-judged way: per-seam breakdown, the Python-floor
        # split and the measured self-overhead accumulate across rounds (the
        # bulky collapsed-stack text stays out — it ships as a file instead)
        line["hostprof"] = {
            key: value
            for key, value in result["hostprof"].items()
            if key != "collapsed"
        }
    if args.chaos_flamegraph:
        collapsed = (result.get("hostprof") or {}).get("collapsed")
        atomic_write_text(
            args.chaos_flamegraph,
            collapsed
            if collapsed
            else "# no host profiler samples captured (profiler not live for"
            " this scenario — run with --chaos-scenario high_tenant)\n",
        )
    if args.chaos_scenario == "host_crash":
        # the cadence-overhead probe rides the host-crash runs: checkpointing
        # on vs off on an identical stream, recorded-never-judged
        probe = _safe(_checkpoint_overhead_probe)
        if probe is not None:
            line["checkpoint"] = probe
    print(json.dumps(line, sort_keys=True, default=str))
    if args.chaos_report:
        atomic_write_text(
            args.chaos_report,
            json.dumps({"report": report, "result": result}, sort_keys=True, default=str, indent=2),
        )
    if args.chaos_trace:
        # the stitched GET /trace/<id> of one injected-NaN batch the replay
        # fetched over HTTP mid-run — proof the lookup plane answers end to end
        sample = (result.get("lineage") or {}).get("sample_trace")
        atomic_write_text(
            args.chaos_trace,
            json.dumps(
                sample if sample is not None else {"error": "no sample trace captured"},
                sort_keys=True,
                default=str,
                indent=2,
            ),
        )
    _record_history(line, check=args.check_regressions)
    if not report["passed"]:
        sys.exit(1)


# ------------------------------------------------------------------------------ main


def _safe(fn, *args):
    try:
        return fn(*args)
    except Exception as err:  # never break the one-line contract
        sys.stderr.write(f"bench config {fn.__name__} failed: {err!r}\n")
        return None


# ---------------------------------------------------------------------- memory


def _memory_snapshot() -> dict:
    """Peak memory of this process: host RSS always, device HBM when reported.

    ``ru_maxrss`` is KiB on Linux and bytes on macOS; device peak comes from
    the guarded ``obs.memory`` poll (CPU backends report nothing → the key is
    simply absent). These ride along in the bench JSON and the history lines
    as recorded-but-never-judged fields (like ``traced``), so memory trends
    accumulate across rounds without gating anything.
    """
    out: dict = {}
    try:
        import resource

        rss = int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)
        out["peak_rss_bytes"] = rss if sys.platform == "darwin" else rss * 1024
    except Exception:
        pass
    try:
        from torchmetrics_tpu.obs import memory as obs_memory

        peak = obs_memory.peak_device_bytes()
        if peak is not None:
            out["device_peak_bytes_in_use"] = int(peak)
    except Exception:
        pass
    return out


def _merge_memory(*snaps) -> dict:
    """Elementwise max across per-process memory snapshots (peaks combine as max)."""
    out: dict = {}
    for snap in snaps:
        if not isinstance(snap, dict):
            continue
        for key, value in snap.items():
            if isinstance(value, (int, float)) and not isinstance(value, bool):
                if key not in out or value > out[key]:
                    out[key] = value
    return out


# ------------------------------------------------------------------------- cost

# per-config XLA cost-ledger summaries (variants compiled + their estimated
# flops/bytes + compile seconds), captured unconditionally by _safe_obs — the
# ledger records at compile time, so unlike TM_TPU_BENCH_OBS this perturbs no
# timed region. Recorded in the JSON line and history, never judged.
_COST_BY_CONFIG: dict = {}


def _cost_mark():
    try:
        from torchmetrics_tpu.obs import cost as obs_cost

        return obs_cost.get_ledger().mark()
    except Exception:
        return None


def _cost_since(name: str, mark) -> None:
    """Accumulate the ledger delta since ``mark`` under config ``name``."""
    if mark is None:
        return
    try:
        from torchmetrics_tpu.obs import cost as obs_cost

        delta = obs_cost.get_ledger().since(mark)
    except Exception:
        return
    if not delta.get("variants_compiled"):
        return
    seen = _COST_BY_CONFIG.setdefault(name, {})
    for key, value in delta.items():
        if isinstance(value, (int, float)):
            seen[key] = round(seen.get(key, 0) + value, 6)


def _cost_snapshot() -> dict:
    """This process's cost view: whole-ledger totals + per-config deltas."""
    out: dict = {}
    try:
        from torchmetrics_tpu.obs import cost as obs_cost

        out["totals"] = obs_cost.get_ledger().totals()
    except Exception:
        pass
    if _COST_BY_CONFIG:
        out["by_config"] = {k: dict(v) for k, v in _COST_BY_CONFIG.items()}
    return out


def _merge_cost(*snaps) -> dict:
    """Combine per-process cost snapshots: totals sum, per-config dicts union."""
    totals: dict = {}
    by_config: dict = {}
    for snap in snaps:
        if not isinstance(snap, dict):
            continue
        for key, value in (snap.get("totals") or {}).items():
            if isinstance(value, (int, float)) and not isinstance(value, bool):
                totals[key] = round(totals.get(key, 0) + value, 6)
        for name, delta in (snap.get("by_config") or {}).items():
            seen = by_config.setdefault(name, {})
            for key, value in (delta or {}).items():
                if isinstance(value, (int, float)) and not isinstance(value, bool):
                    seen[key] = round(seen.get(key, 0) + value, 6)
    out: dict = {}
    if totals:
        out["totals"] = totals
    if by_config:
        out["by_config"] = by_config
    return out


# ------------------------------------------------------------------ observability

# TM_TPU_BENCH_OBS=1 runs each config WITH obs tracing enabled and attaches
# per-config telemetry summaries — the timed numbers for such a round include
# the tracing overhead (a few percent on the µs-scale configs), so they must
# not be compared against untraced rounds. Off by default: the default-round
# numbers stay comparable across rounds (the instrumented-but-disabled runtime
# is within noise of the seed — asserted by tests/core/test_observability.py).
_BENCH_OBS = os.environ.get("TM_TPU_BENCH_OBS", "0") == "1"


def _obs_counters_summary(rec) -> dict:
    """Compact JSON-able view of a recorder: counters + span totals."""
    snap = rec.snapshot()

    def _series_key(entry):
        labels = ",".join(f"{k}={v}" for k, v in sorted(entry["labels"].items()))
        return entry["name"] + ("{" + labels + "}" if labels else "")

    return {
        "counters": {_series_key(c): c["value"] for c in snap["counters"]},
        "gauges": {_series_key(g): g["value"] for g in snap["gauges"]},
        "spans": {
            _series_key(h): {"count": h["count"], "total_ms": round(h["sum"] * 1e3, 3)}
            for h in snap["histograms"]
        },
        "dropped_events": snap["dropped_events"],
    }


def _safe_obs(obs_out, name, fn, *args):
    """``_safe`` plus per-config obs capture when TM_TPU_BENCH_OBS=1.

    Interleaved timing rounds run each config more than once; the summaries
    AGGREGATE across rounds (counters/span totals summed) so the attached
    telemetry describes every run of the config, not just the last (warm-cache)
    round, while the timed numbers remain per-config minima. Independent of
    TM_TPU_BENCH_OBS, the per-config XLA cost-ledger delta (variants compiled,
    estimated flops/bytes) is always captured — ledger capture is compile-time
    only, so it cannot perturb the timed region.
    """
    cost_mark = _cost_mark()
    if not _BENCH_OBS:
        value = _safe(fn, *args)
        _cost_since(name, cost_mark)
        return value
    from torchmetrics_tpu import obs

    with obs.observe() as rec:
        value = _safe(fn, *args)
    _cost_since(name, cost_mark)
    summary = _obs_counters_summary(rec)
    seen = obs_out.get(name)
    if seen is None:
        obs_out[name] = summary
    else:
        for key, val in summary["counters"].items():
            seen["counters"][key] = seen["counters"].get(key, 0) + val
        seen["gauges"].update(summary["gauges"])
        for key, span in summary["spans"].items():
            if key in seen["spans"]:
                seen["spans"][key] = {
                    "count": seen["spans"][key]["count"] + span["count"],
                    "total_ms": round(seen["spans"][key]["total_ms"] + span["total_ms"], 3),
                }
            else:
                seen["spans"][key] = span
        seen["dropped_events"] += summary["dropped_events"]
    return value


def _obs_demo() -> dict:
    """Scripted 3-metric instrumented mini-run (jit hits/misses + compile spans,
    a faked 2-host collective sync, one guarded NaN batch) so every bench line
    demonstrates the full obs egress without perturbing the timed configs."""
    import warnings

    try:
        import jax.numpy as jnp
        from unittest import mock

        from torchmetrics_tpu import obs
        from torchmetrics_tpu.aggregation import MeanMetric
        from torchmetrics_tpu.classification import MulticlassAccuracy
        from torchmetrics_tpu.parallel import sync as sync_mod
        from torchmetrics_tpu.regression import MeanSquaredError

        rng = np.random.RandomState(0)
        with obs.observe() as rec:
            acc = MulticlassAccuracy(num_classes=4, validate_args=False)
            mse = MeanSquaredError(error_policy="warn_skip")
            mean = MeanMetric()
            for _ in range(4):
                acc.update(
                    jnp.asarray(rng.rand(64, 4).astype(np.float32)),
                    jnp.asarray(rng.randint(0, 4, 64)),
                )
                mse.update(
                    jnp.asarray(rng.rand(32).astype(np.float32)),
                    jnp.asarray(rng.rand(32).astype(np.float32)),
                )
                mean.update(jnp.asarray(rng.rand(8).astype(np.float32)))
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                mse.update(jnp.full((8,), np.nan), jnp.zeros((8,)))
                # faked 2-host world: per-collective timing/payload events
                with mock.patch.object(sync_mod, "distributed_available", lambda: True), mock.patch(
                    "jax.experimental.multihost_utils.process_allgather",
                    lambda x, tiled=False: jnp.stack([jnp.asarray(x)] * 2),
                ):
                    synced = MeanSquaredError(distributed_available_fn=lambda: True)
                    synced.update(jnp.ones(16), jnp.zeros(16))
                    synced.sync()
                    synced.unsync()
            for metric in (acc, mse, mean):
                np.asarray(metric.compute())
        summary = _obs_counters_summary(rec)
        summary["robust"] = {
            "MeanSquaredError": {
                "updates_ok": mse.updates_ok,
                "updates_skipped": mse.updates_skipped,
                "updates_quarantined": mse.updates_quarantined,
            }
        }
        return summary
    except Exception as err:
        sys.stderr.write(f"bench obs demo failed: {err!r}\n")
        return {"error": repr(err)}


def _engine_configs(obs_by_config: dict, preds, target) -> dict:
    """Both engine configs as flat keys + an `engine_stats` side-channel dict."""
    out: dict = {}
    stats: dict = {}
    for name, fuse in (("engine_pipelined", 1), ("engine_fused", 8)):
        res = _safe_obs(obs_by_config, name, bench_acc_engine, preds, target, fuse)
        if res is not None:
            out[name], stats[name] = res
    if stats:
        out["engine_stats"] = stats
    return out


def _mux_configs(obs_by_config: dict, preds, target) -> dict:
    """Both multiplexer configs as flat keys + a `mux_stats` side channel."""
    out: dict = {}
    stats: dict = {}
    for name, n_tenants in (("multiplexed_8tenants", 8), ("multiplexed_64tenants", 64)):
        res = _safe_obs(obs_by_config, name, bench_acc_mux, preds, target, n_tenants)
        if res is not None:
            out[name], stats[name] = res
    if stats:
        out["mux_stats"] = stats
    return out


def _run_ours(hardware: str) -> dict:
    """Measure our configs in THIS process (backend already chosen)."""
    preds, target = _stage_data()
    obs_by_config: dict = {}
    out = {
        "stateful": _safe_obs(obs_by_config, "stateful", bench_acc_stateful, preds, target),
        "scan": _safe_obs(obs_by_config, "scan", bench_acc_scan, preds, target),
        **_engine_configs(obs_by_config, preds, target),
        **_mux_configs(obs_by_config, preds, target),
        **(_safe(bench_sync_overhead_stats) or {}),
        "curve": _safe_obs(obs_by_config, "curve", bench_pr_curve),
        "inception": _safe_obs(obs_by_config, "inception", bench_inception, hardware),
        "clip": _safe_obs(obs_by_config, "clip", bench_clip_score, hardware),
        "bert": _safe_obs(obs_by_config, "bert", bench_bert_score, hardware),
        "perplexity": _safe_obs(obs_by_config, "perplexity", bench_perplexity),
        "rouge": _safe_obs(obs_by_config, "rouge", bench_rouge),
    }
    out["obs_demo"] = _obs_demo()
    if obs_by_config:
        out["obs_configs"] = obs_by_config
    return out


def _worker_main(mode: str) -> None:
    """Subprocess entry: emit one JSON dict of raw config values on stdout.

    The CPU fallback must NOT run the single-chip configs on the 8-virtual-device
    mesh — on a small host the extra device threads oversubscribe the cores and the
    numbers measure contention, not the kernels (this polluted BENCH_r02). Single-chip
    configs get a 1-device process; only the mesh config gets the 8-device process.
    """
    from _jax_cpu_force import force_cpu

    def _min_merge(acc: dict, new: dict) -> None:
        for k, v in new.items():
            if v is not None and (acc.get(k) is None or v < acc[k]):
                acc[k] = v

    out: dict = {}
    if mode == "single":
        force_cpu(1)
        preds, target = _stage_data()
        _safe(_reference_modules)
        obs_by_config: dict = {}
        # interleave ours/reference rounds and keep per-config minima: a shared/noisy
        # host drifts ±30% between runs, which biased BENCH_r02 — alternating rounds
        # in one process exposes both sides to the same drift
        for _ in range(2):
            _min_merge(out, {
                "stateful": _safe_obs(obs_by_config, "stateful", bench_acc_stateful, preds, target),
                "ref_stateful": _safe(ref_acc_stateful),
                "scan": _safe_obs(obs_by_config, "scan", bench_acc_scan, preds, target),
                "curve": _safe_obs(obs_by_config, "curve", bench_pr_curve),
                "ref_curve": _safe(ref_pr_curve),
            })
        _min_merge(out, {
            "inception": _safe_obs(obs_by_config, "inception", bench_inception, "cpu-fallback"),
            "clip": _safe_obs(obs_by_config, "clip", bench_clip_score, "cpu-fallback"),
            "bert": _safe_obs(obs_by_config, "bert", bench_bert_score, "cpu-fallback"),
            "perplexity": _safe_obs(obs_by_config, "perplexity", bench_perplexity),
            "ref_perplexity": _safe(ref_perplexity),
            "rouge": _safe_obs(obs_by_config, "rouge", bench_rouge),
            "ref_rouge": _safe(ref_rouge),
        })
        # engine/mux configs carry a non-numeric stats dict, so they stay outside
        # the min-merge (their timings are single-round like the model configs)
        out.update(_engine_configs(obs_by_config, preds, target))
        out.update(_mux_configs(obs_by_config, preds, target))
        out["obs_demo"] = _obs_demo()
        if obs_by_config:
            out["obs_configs"] = obs_by_config
    elif mode == "mesh":
        force_cpu(8)
        _safe(_reference_modules)
        stats = _safe(bench_sync_overhead_stats) or {}
        out.update(stats)
        for _ in range(2):
            _min_merge(out, {"ref_collection": _safe(ref_collection)})
    elif mode == "hotops":
        # NO force_cpu: inherits the pinned TPU backend; TM_TPU_USE_PALLAS comes
        # from the spawning process's env (the A/B lever)
        out = bench_hotops()
    out["memory"] = _memory_snapshot()  # the worker did the work; its peaks count
    out["cost"] = _cost_snapshot()  # the worker's ledger holds its configs' compiles
    print(json.dumps(out))


def _run_fallback_via_workers() -> dict:
    """Run the config suite split across 1-device and 8-device CPU subprocesses."""
    merged: dict = {}
    for mode in ("single", "mesh"):
        try:
            proc = subprocess.run(
                [sys.executable, __file__, "--worker", mode],
                capture_output=True, text=True, timeout=1200,
            )
            if proc.returncode == 0 and proc.stdout.strip():
                data = json.loads(proc.stdout.strip().splitlines()[-1])
                # peaks combine as max across workers, not last-writer-wins
                merged["memory"] = _merge_memory(merged.get("memory"), data.pop("memory", None))
                # cost ledgers are per-process: totals sum, config deltas union
                merged["cost"] = _merge_cost(merged.get("cost"), data.pop("cost", None))
                merged.update(data)
            else:
                sys.stderr.write(f"bench worker {mode} rc={proc.returncode}: {proc.stderr[-500:]}\n")
        except Exception as err:
            sys.stderr.write(f"bench worker {mode} failed: {err!r}\n")
    return merged


def _run_pallas_ab() -> dict:
    """On real TPU hardware: run the kernel-backed hot ops with the Pallas kernels
    off and on (subprocess env is the only reliable lever — the jit caches in a
    live process would otherwise pin the first trace's choice)."""
    ab = {}
    for arm, flag in (("xla", "0"), ("pallas", "1")):
        env = dict(os.environ, TM_TPU_USE_PALLAS=flag)
        try:
            proc = subprocess.run(
                [sys.executable, __file__, "--worker", "hotops"],
                capture_output=True, text=True, timeout=900, env=env,
            )
            if proc.returncode == 0 and proc.stdout.strip():
                ab[arm] = json.loads(proc.stdout.strip().splitlines()[-1])
            else:
                sys.stderr.write(f"pallas A/B arm {arm} rc={proc.returncode}: {proc.stderr[-400:]}\n")
        except Exception as err:
            sys.stderr.write(f"pallas A/B arm {arm} failed: {err!r}\n")
    if "xla" in ab and "pallas" in ab:
        ab["speedup"] = {
            op: round(ab["xla"][op] / ab["pallas"][op], 3)
            for op in ab["xla"]
            if isinstance(ab["xla"].get(op), (int, float))
            and isinstance(ab["pallas"].get(op), (int, float))
            and ab["pallas"][op] > 0
        }
    return ab


def _record_history(result: dict, check: bool) -> None:
    """Append this run to BENCH_HISTORY.jsonl; with ``check``, gate on regressions.

    Without ``check`` the append is best-effort (a default bench round must
    never die on its bookkeeping). With ``check`` this IS the CI gate, so a
    sentinel that cannot run is itself a failure: exits 2 on import/load/append
    errors (matching the standalone CLI) and 1 on a breach. The regression
    table goes to stderr so the one-JSON-line stdout contract holds.
    """
    try:
        from torchmetrics_tpu.obs import regress
    except Exception as err:
        sys.stderr.write(f"bench history: obs.regress unavailable ({err!r})\n")
        if check:
            sys.exit(2)  # a gate that cannot run must not pass
        return
    try:
        history = (
            regress.load_history(_HISTORY_PATH)
            if check and os.path.exists(_HISTORY_PATH)
            else []
        )
        # traced rounds (TM_TPU_BENCH_OBS=1) carry tracing overhead in their
        # timings: recorded for the telemetry, tagged so they are never used
        # as baselines and never judged
        record = regress.append_history(result, path=_HISTORY_PATH, traced=_BENCH_OBS)
    except Exception as err:
        sys.stderr.write(f"bench history append failed: {err!r}\n")
        if check:
            sys.exit(2)
        return
    if not check:
        return
    if record.get("traced"):
        sys.stderr.write(
            "bench regression check skipped: traced round (TM_TPU_BENCH_OBS=1) timings"
            " are not comparable with untraced history.\n"
        )
        return
    rows = regress.check_regressions(record, history)
    sys.stderr.write(regress.format_table(rows, hardware=record.get("hardware")))
    if any(row.get("regressed") for row in rows):
        sys.exit(1)


def main(check_regressions: bool = False) -> None:
    hardware = _acquire_backend()
    if hardware == "cpu-fallback":
        ours = _run_fallback_via_workers()
        # reference numbers come interleaved from the same worker processes
        ref_stateful = ours.get("ref_stateful")
        ref_col = ours.get("ref_collection")
        ref_curve = ours.get("ref_curve")
        pallas_ab = {"note": "skipped: Pallas kernels require TPU hardware (interpret-mode parity is covered in tests)"}
    else:
        ours = _run_ours(hardware)
        _safe(_reference_modules)
        ref_stateful = _safe(ref_acc_stateful)
        ref_col = _safe(ref_collection)
        ref_curve = _safe(ref_pr_curve)
        ours["ref_perplexity"] = _safe(ref_perplexity)
        ours["ref_rouge"] = _safe(ref_rouge)
        pallas_ab = _run_pallas_ab()
    ours_stateful = ours.get("stateful")
    ours_scan = ours.get("scan")
    ours_collection = ours.get("collection")
    ours_curve = ours.get("curve")
    ours_incep = ours.get("inception")

    def ratio(ref, ours):
        if ref is None or ours is None or ours <= 0:
            return None
        return round(ref / ours, 3)

    def ratio_inv(ref, ours):
        # throughput configs: higher is better, so vs_baseline = ours / ref
        if ref is None or ours is None or ref <= 0:
            return None
        return round(ours / ref, 3)

    def _sync_overhead_pct(with_sync, without_sync):
        if with_sync is None or without_sync is None or with_sync <= 0:
            return None
        return round(max(0.0, (with_sync - without_sync) / with_sync * 100.0), 2)

    def _mux_baseline(ours_dict, name):
        # the multiplexer configs' baseline is measured in the same run: the
        # identical traffic through per-tenant pipeline sessions
        stats = (ours_dict.get("mux_stats") or {}).get(name) or {}
        return stats.get("per_tenant_pipelines_us_per_update")

    configs = {
        "acc_update_stateful": {
            "value": ours_stateful, "unit": "us/step", "baseline": ref_stateful,
            "vs_baseline": ratio(ref_stateful, ours_stateful),
        },
        "acc_update_scan": {
            "value": ours_scan, "unit": "us/step", "baseline": ref_stateful,
            "vs_baseline": ratio(ref_stateful, ours_scan),
        },
        "acc_update_engine_pipelined": {
            "value": ours.get("engine_pipelined"), "unit": "us/step", "baseline": ref_stateful,
            "vs_baseline": ratio(ref_stateful, ours.get("engine_pipelined")),
            "note": "config #1 loop through the streaming engine, fuse=1: prefetch +"
                    " bounded async window, one dispatch per step (engine overhead floor)",
        },
        "acc_update_engine_fused": {
            "value": ours.get("engine_fused"), "unit": "us/step", "baseline": ref_stateful,
            "vs_baseline": ratio(ref_stateful, ours.get("engine_fused")),
            "note": "config #1 loop through the streaming engine, fuse=8: 8 batches per"
                    " lax.scan dispatch after AOT warmup; dispatch/warmup/compile-cache"
                    " stats ride in the top-level `engine` key (recorded, never judged)",
        },
        "acc_update_multiplexed_8tenants": {
            "value": ours.get("multiplexed_8tenants"), "unit": "us/step",
            "baseline": _mux_baseline(ours, "multiplexed_8tenants"),
            "vs_baseline": ratio(
                _mux_baseline(ours, "multiplexed_8tenants"), ours.get("multiplexed_8tenants")
            ),
            "note": "8 tenant sessions through ONE cross-tenant fused vmap dispatch"
                    " (256-row accuracy batches, AOT-warmed); baseline = the same"
                    " traffic through 8 per-tenant pipeline sessions; variant counts"
                    " ride in the top-level `mux` key (recorded, never judged)",
        },
        "acc_update_multiplexed_64tenants": {
            "value": ours.get("multiplexed_64tenants"), "unit": "us/step",
            "baseline": _mux_baseline(ours, "multiplexed_64tenants"),
            "vs_baseline": ratio(
                _mux_baseline(ours, "multiplexed_64tenants"), ours.get("multiplexed_64tenants")
            ),
            "note": "64 tenant sessions through ONE cross-tenant fused vmap dispatch;"
                    " the compiled-program collapse (O(buckets) vs O(tenants)) is the"
                    " structural claim — see the `mux` key's compiled_variants",
        },
        "collection_acc_f1_auroc_mesh_sync": {
            "value": ours_collection, "unit": "us/step", "baseline": ref_col,
            "vs_baseline": ratio(ref_col, ours_collection),
            "note": "ours includes mesh sync every step; reference baseline is eager update+compute without any DDP sync",
        },
        "pr_curve_binned_50x4096": {
            "value": ours_curve, "unit": "ms/epoch", "baseline": ref_curve,
            "vs_baseline": ratio(ref_curve, ours_curve),
        },
        "inception_v3_features": {
            "value": ours_incep, "unit": "imgs/sec", "baseline": None, "vs_baseline": None,
            "note": "reference needs torch-fidelity weights (not installed); FLOPs-identical random-weight net",
        },
        "clip_score": {
            "value": ours.get("clip"), "unit": "samples/sec", "baseline": None, "vs_baseline": None,
            "note": "ViT-B/32-dims random weights on TPU, tiny fabricated CLIP on the CPU fallback;"
                    " reference downloads weights (no egress here)",
        },
        "bert_score": {
            "value": ours.get("bert"), "unit": "samples/sec", "baseline": None, "vs_baseline": None,
            "note": "BERT-base encoder dims (random weights) on TPU, tiny on the CPU fallback;"
                    " reference downloads weights (no egress here)",
        },
        "perplexity_8x128x8192": {
            "value": ours.get("perplexity"), "unit": "samples/sec",
            "baseline": ours.get("ref_perplexity"),
            "vs_baseline": ratio_inv(ours.get("ref_perplexity"), ours.get("perplexity")),
            "note": "cpu-fallback floor, attributed-final: XLA:CPU's exp primitive is"
                    " ~1.3x slower than torch's MKL VML; our fused lse already costs"
                    " the same as bare exp+sum (microbench table in PERF.md)",
        },
        "rouge_corpus_64": {
            "value": ours.get("rouge"), "unit": "samples/sec",
            "baseline": ours.get("ref_rouge"),
            "vs_baseline": ratio_inv(ours.get("ref_rouge"), ours.get("rouge")),
        },
        "mesh_sync_overhead_pct": {
            "value": ours.get(
                "sync_overhead_pct_median",
                _sync_overhead_pct(ours.get("collection"), ours.get("collection_nosync")),
            ),
            "unit": "% of step time", "baseline": 2.0,
            "vs_baseline": None,
            "spread": {
                "min": ours.get("sync_overhead_pct_min"),
                "max": ours.get("sync_overhead_pct_max"),
                "reps": ours.get("sync_overhead_reps"),
            },
            "scaling_curve_by_devices": ours.get("sync_overhead_curve"),
            "note": "BASELINE.md north star: metric-sync overhead < 2% of step time"
                    " (sync-every-step vs identical step without collectives)."
                    " Median over interleaved repeated rounds with min-max spread and"
                    " a device-scaling curve; on the oversubscribed 1-core cpu-fallback"
                    " host the spread bounds the claim, on real TPU it tightens",
        },
    }
    for cfg in configs.values():
        if isinstance(cfg.get("value"), float):
            cfg["value"] = round(cfg["value"], 2)
        if isinstance(cfg.get("baseline"), float):
            cfg["baseline"] = round(cfg["baseline"], 2)

    obs_summary = {"demo_3_metric_run": ours.get("obs_demo")}
    if ours.get("obs_configs"):
        obs_summary["per_config"] = ours["obs_configs"]
    result = {
        "metric": f"MulticlassAccuracy per-step update+compute (4096x100, {STEPS} steps)",
        "value": round(ours_stateful, 2) if ours_stateful else None,
        "unit": "us/step",
        "vs_baseline": ratio(ref_stateful, ours_stateful),
        "hardware": hardware,
        "configs": configs,
        "pallas_ab": pallas_ab,
        "obs": obs_summary,
        # streaming-engine accounting (timed-run dispatch counts, fused chunk
        # sizes, AOT-warmup compile totals, persistent-compile-cache hits):
        # recorded in the JSON line and the history record, never judged
        "engine": ours.get("engine_stats"),
        # cross-tenant multiplexer accounting (timings, per-side compiled
        # variants, speedup vs per-tenant pipelines) — recorded, never judged
        "mux": ours.get("mux_stats"),
        # peak host RSS (+ device HBM peak when the backend reports it), max
        # across this process and the workers; recorded in the history line,
        # never judged by the regression gate
        "memory": _merge_memory(_memory_snapshot(), ours.get("memory")),
        # XLA cost-ledger summary (per-config variants compiled + estimated
        # flops/bytes, whole-run compile/dispatch totals across this process
        # and the workers); recorded in the history line, never judged
        "cost": _merge_cost(_cost_snapshot(), ours.get("cost")),
    }
    print(json.dumps(result))
    _record_history(result, check=check_regressions)


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--worker":
        _worker_main(sys.argv[2])
    elif "--chaos" in sys.argv[1:]:
        _chaos_main(sys.argv[1:])
    else:
        main(check_regressions="--check-regressions" in sys.argv[1:])
