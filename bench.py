"""Benchmark: TPU-native metrics vs reference TorchMetrics (torch CPU).

Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "hardware": ...,
   "configs": {...}}``

Headline = config #1 (per-step stateful update+compute — the apples-to-apples hot
loop: our jit-cached dispatch vs the reference's eager per-step update). The
``configs`` dict carries every BASELINE.md config measured this run, each with its own
``vs_baseline`` (``null`` where the reference cannot run in this image).

Backend policy: the host pins ``JAX_PLATFORMS=axon`` (tunneled TPU) and the tunnel has
been wedged at bench time in past rounds. We probe the backend *in a subprocess* (a
wedged tunnel hangs forever, it doesn't error), retry with backoff at bench time, and
only then fall back to an 8-device virtual CPU mesh tagged ``cpu-fallback``.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

BATCH = 4096
NUM_CLASSES = 100
STEPS = 120


# --------------------------------------------------------------------------- backend


def _probe_once(timeout_s: int = 75):
    probe = "import jax; d = jax.devices(); print(d[0].platform)"
    try:
        out = subprocess.run(
            [sys.executable, "-c", probe], capture_output=True, text=True, timeout=timeout_s
        )
        if out.returncode == 0 and out.stdout.strip():
            return out.stdout.strip().splitlines()[-1]
    except subprocess.TimeoutExpired:
        pass
    return None


def _acquire_backend() -> str:
    """Probe the pinned backend with retry+backoff *now* (bench time), then fall back.

    Round-1/2 postmortem: a single early probe that never re-checks turned one transient
    tunnel outage into a whole round of CPU numbers. Three probes spread over ~3 minutes
    is cheap insurance against a relay that is restarting.
    """
    for wait in (0, 30, 60):
        if wait:
            time.sleep(wait)
        platform = _probe_once()
        if platform:
            return platform
    # JAX is deliberately NOT initialised in the main process on fallback — the
    # worker subprocesses each pin their own device count (1 vs 8)
    return "cpu-fallback"


# ------------------------------------------------------------------- reference setup


def _install_lightning_utilities_stub() -> None:
    """Minimal in-memory stand-in for the reference's `lightning_utilities` dependency
    (not installed in this image) so the baseline can be measured."""
    import importlib
    import importlib.util
    import types
    from enum import Enum

    if "lightning_utilities" in sys.modules:
        return

    def package_available(name: str) -> bool:
        try:
            return importlib.util.find_spec(name) is not None
        except Exception:
            return False

    class RequirementCache:
        def __init__(self, requirement: str = "", module: str = None) -> None:
            self.requirement = requirement
            self.module = module

        def __bool__(self) -> bool:
            name = self.module or self.requirement.split(">")[0].split("<")[0].split("=")[0].strip()
            try:
                importlib.import_module(name)
                return True
            except Exception:
                return False

        def __str__(self) -> str:
            return self.requirement

    class StrEnum(str, Enum):
        @classmethod
        def from_str(cls, value, source="key"):
            for member in cls:
                if member.value.lower() == str(value).lower().replace("-", "_"):
                    return member
            raise ValueError(f"Invalid value {value!r} for {cls.__name__}")

    def apply_to_collection(data, dtype, function, *args, **kwargs):
        if isinstance(data, dtype):
            return function(data, *args, **kwargs)
        if isinstance(data, dict):
            return {k: apply_to_collection(v, dtype, function, *args, **kwargs) for k, v in data.items()}
        if isinstance(data, (list, tuple)):
            return type(data)(apply_to_collection(v, dtype, function, *args, **kwargs) for v in data)
        return data

    root = types.ModuleType("lightning_utilities")
    core = types.ModuleType("lightning_utilities.core")
    imports_mod = types.ModuleType("lightning_utilities.core.imports")
    enums_mod = types.ModuleType("lightning_utilities.core.enums")
    apply_mod = types.ModuleType("lightning_utilities.core.apply_func")
    imports_mod.package_available = package_available
    imports_mod.RequirementCache = RequirementCache
    imports_mod.compare_version = lambda *a, **k: True
    enums_mod.StrEnum = StrEnum
    apply_mod.apply_to_collection = apply_to_collection
    root.apply_to_collection = apply_to_collection
    root.core = core
    core.imports = imports_mod
    core.enums = enums_mod
    core.apply_func = apply_mod
    sys.modules["lightning_utilities"] = root
    sys.modules["lightning_utilities.core"] = core
    sys.modules["lightning_utilities.core.imports"] = imports_mod
    sys.modules["lightning_utilities.core.enums"] = enums_mod
    sys.modules["lightning_utilities.core.apply_func"] = apply_mod


def _reference_modules():
    """Import the reference TorchMetrics from /root/reference (torch CPU)."""
    _install_lightning_utilities_stub()
    if "/root/reference/src" not in sys.path:
        sys.path.insert(0, "/root/reference/src")
    import torchmetrics  # noqa: F401

    return torchmetrics


# ------------------------------------------------------------------------ our configs


def _stage_data():
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    preds = jnp.asarray(rng.rand(STEPS, BATCH, NUM_CLASSES).astype(np.float32))
    target = jnp.asarray(rng.randint(0, NUM_CLASSES, (STEPS, BATCH)))
    return preds, target


def bench_acc_stateful(preds, target) -> float:
    """Config #1: per-step stateful ``metric.update`` loop + one ``compute``.

    This is the same call pattern a user writes and the same pattern the reference
    baseline runs eagerly: one update per step, jit-cached dispatch per call.
    """
    import jax

    from torchmetrics_tpu.classification import MulticlassAccuracy

    metric = MulticlassAccuracy(num_classes=NUM_CLASSES, average="micro", validate_args=False)
    # pre-split batches: slicing the stacked stream inside the loop would charge a
    # per-step device copy that the eager reference baseline never pays
    n_distinct = 8
    batches = [(preds[i], target[i]) for i in range(n_distinct)]
    jax.block_until_ready(batches)
    metric.update(*batches[0])
    jax.block_until_ready(metric.compute())
    metric.reset()

    start = time.perf_counter()
    for i in range(STEPS):
        p, t = batches[i % n_distinct]
        metric.update(p, t)
    jax.block_until_ready(metric.compute())
    elapsed = time.perf_counter() - start
    return elapsed / STEPS * 1e6


def bench_acc_scan(preds, target) -> float:
    """Config #2: whole epoch folded through ``lax.scan`` in ONE XLA program."""
    import jax

    from torchmetrics_tpu.classification import MulticlassAccuracy

    metric = MulticlassAccuracy(num_classes=NUM_CLASSES, average="micro", validate_args=False)

    @jax.jit
    def run_epoch(state, preds, target):
        state = metric.scan_update(state, preds, target)
        return metric.pure_compute(state), state

    value, _ = run_epoch(metric.init_state(), preds, target)
    jax.block_until_ready(value)

    reps = 2
    start = time.perf_counter()
    for _ in range(reps):
        value, _ = run_epoch(metric.init_state(), preds, target)
        jax.block_until_ready(value)
    elapsed = time.perf_counter() - start
    return elapsed / (STEPS * reps) * 1e6


def bench_collection_mesh_sync() -> float:
    """Config #3: Accuracy+F1+AUROC update & mesh sync per step (BASELINE.md config 2).

    Jitted shard_map step over every available device: per-shard pure updates of the
    two compute groups (stat-scores shared by Acc/F1; binned-curve for AUROC) + psum
    sync — the production distributed pattern. The reference baseline runs the same
    three metrics eagerly WITHOUT any sync (its DDP needs a process group we can't
    spawn here), so its number is a lower bound for the reference.
    """
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from torchmetrics_tpu.classification import MulticlassAccuracy, MulticlassAUROC, MulticlassF1Score

    n_classes = 10
    devices = np.array(jax.devices())
    mesh = Mesh(devices, ("data",))
    n_dev = len(devices)
    per_step = 1024 * n_dev

    rng = np.random.RandomState(0)
    preds = jnp.asarray(rng.rand(per_step, n_classes).astype(np.float32))
    target = jnp.asarray(rng.randint(0, n_classes, (per_step,)))

    acc = MulticlassAccuracy(num_classes=n_classes, average="macro", validate_args=False)
    f1 = MulticlassF1Score(num_classes=n_classes, average="macro", validate_args=False)
    auroc = MulticlassAUROC(num_classes=n_classes, thresholds=100, validate_args=False)

    def step(states, p, t):
        s_stat, s_curve = states
        # Acc and F1 share one stat-scores state (what MetricCollection's compute
        # groups dedup to); AUROC keeps the binned-curve state.
        s_stat = acc.pure_update(s_stat, p, t)
        s_curve = auroc.pure_update(s_curve, p, t)
        sy_stat = acc.sync_state(s_stat, axis_name="data")
        sy_curve = auroc.sync_state(s_curve, axis_name="data")
        vals = (acc.pure_compute(sy_stat), f1.pure_compute(sy_stat), auroc.pure_compute(sy_curve))
        return (s_stat, s_curve), vals

    f = jax.jit(
        shard_map(
            step,
            mesh=mesh,
            in_specs=((P(), P()), P("data"), P("data")),
            out_specs=((P(), P()), (P(), P(), P())),
            check_vma=False,
        )
    )
    states = (acc.init_state(), auroc.init_state())
    states, vals = f(states, preds, target)
    jax.block_until_ready(vals)

    iters = 30
    start = time.perf_counter()
    for _ in range(iters):
        states, vals = f(states, preds, target)
    jax.block_until_ready(vals)
    return (time.perf_counter() - start) / iters * 1e6


def bench_pr_curve() -> float:
    """Config #5-ish: binned multiclass PR-curve, 50 update steps + compute (ms total)."""
    import jax

    from torchmetrics_tpu.classification import MulticlassPrecisionRecallCurve

    import jax.numpy as jnp

    n_classes = 10
    steps = 50
    rng = np.random.RandomState(0)
    preds = jnp.asarray(rng.rand(steps, BATCH, n_classes).astype(np.float32))
    target = jnp.asarray(rng.randint(0, n_classes, (steps, BATCH)))

    metric = MulticlassPrecisionRecallCurve(num_classes=n_classes, thresholds=200, validate_args=False)

    @jax.jit
    def run(state, preds, target):
        state = metric.scan_update(state, preds, target)
        return metric.pure_compute(state)

    out = run(metric.init_state(), preds, target)
    jax.block_until_ready(out)
    start = time.perf_counter()
    jax.block_until_ready(run(metric.init_state(), preds, target))
    return (time.perf_counter() - start) * 1e3


def bench_inception(hardware: str) -> float:
    """Config #4: FID-path Inception-v3 feature extraction throughput (imgs/sec).

    Random weights — identical FLOPs/layout to the pretrained net, so imgs/sec is
    representative even though scores would not be. Smaller batch on the CPU fallback
    so the config is never silently skipped.
    """
    import warnings

    import jax.numpy as jnp

    from torchmetrics_tpu.image._inception_net import InceptionFeatureExtractor

    on_cpu = hardware.startswith("cpu")
    batch, iters = (8, 2) if on_cpu else (64, 5)

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        ext = InceptionFeatureExtractor(feature=2048)
    imgs = jnp.zeros((batch, 3, 299, 299), dtype=jnp.uint8)
    ext(imgs).block_until_ready()  # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        out = ext(imgs)
    out.block_until_ready()
    return batch * iters / (time.perf_counter() - t0)


# ------------------------------------------------------------------ reference configs


def ref_acc_stateful() -> float:
    import torch

    from torchmetrics.classification import MulticlassAccuracy as TMAcc

    rng = np.random.RandomState(0)
    preds = torch.from_numpy(rng.rand(BATCH, NUM_CLASSES).astype(np.float32))
    target = torch.from_numpy(rng.randint(0, NUM_CLASSES, (BATCH,)))
    metric = TMAcc(num_classes=NUM_CLASSES, average="micro", validate_args=False)
    for _ in range(10):
        metric.update(preds, target)
    metric.compute()
    metric.reset()
    start = time.perf_counter()
    for _ in range(STEPS):
        metric.update(preds, target)
    metric.compute()
    return (time.perf_counter() - start) / STEPS * 1e6


def ref_collection() -> float:
    import torch

    from torchmetrics import MetricCollection
    from torchmetrics.classification import MulticlassAccuracy, MulticlassAUROC, MulticlassF1Score

    n_classes = 10
    n_dev = 8  # match the per-step element count of our mesh config
    per_step = 1024 * n_dev
    rng = np.random.RandomState(0)
    preds = torch.from_numpy(rng.rand(per_step, n_classes).astype(np.float32))
    target = torch.from_numpy(rng.randint(0, n_classes, (per_step,)))
    col = MetricCollection([
        MulticlassAccuracy(num_classes=n_classes, average="macro", validate_args=False),
        MulticlassF1Score(num_classes=n_classes, average="macro", validate_args=False),
        MulticlassAUROC(num_classes=n_classes, thresholds=100, validate_args=False),
    ])
    for _ in range(3):
        col.update(preds, target)
    col.compute()
    col.reset()
    iters = 50
    start = time.perf_counter()
    for _ in range(iters):
        col.update(preds, target)
        col.compute()
    return (time.perf_counter() - start) / iters * 1e6


def ref_pr_curve() -> float:
    import torch

    from torchmetrics.classification import MulticlassPrecisionRecallCurve as TMCurve

    n_classes = 10
    steps = 50
    rng = np.random.RandomState(0)
    preds = torch.from_numpy(rng.rand(steps, BATCH, n_classes).astype(np.float32))
    target = torch.from_numpy(rng.randint(0, n_classes, (steps, BATCH)))
    metric = TMCurve(num_classes=n_classes, thresholds=200, validate_args=False)
    metric.update(preds[0], target[0])
    metric.compute()
    metric.reset()
    start = time.perf_counter()
    for i in range(steps):
        metric.update(preds[i], target[i])
    metric.compute()
    return (time.perf_counter() - start) * 1e3


# ------------------------------------------------------------------------------ main


def _safe(fn, *args):
    try:
        return fn(*args)
    except Exception as err:  # never break the one-line contract
        sys.stderr.write(f"bench config {fn.__name__} failed: {err!r}\n")
        return None


def _run_ours(hardware: str) -> dict:
    """Measure our configs in THIS process (backend already chosen)."""
    preds, target = _stage_data()
    return {
        "stateful": _safe(bench_acc_stateful, preds, target),
        "scan": _safe(bench_acc_scan, preds, target),
        "collection": _safe(bench_collection_mesh_sync),
        "curve": _safe(bench_pr_curve),
        "inception": _safe(bench_inception, hardware),
    }


def _worker_main(mode: str) -> None:
    """Subprocess entry: emit one JSON dict of raw config values on stdout.

    The CPU fallback must NOT run the single-chip configs on the 8-virtual-device
    mesh — on a small host the extra device threads oversubscribe the cores and the
    numbers measure contention, not the kernels (this polluted BENCH_r02). Single-chip
    configs get a 1-device process; only the mesh config gets the 8-device process.
    """
    from _jax_cpu_force import force_cpu

    def _min_merge(acc: dict, new: dict) -> None:
        for k, v in new.items():
            if v is not None and (acc.get(k) is None or v < acc[k]):
                acc[k] = v

    out: dict = {}
    if mode == "single":
        force_cpu(1)
        preds, target = _stage_data()
        _safe(_reference_modules)
        # interleave ours/reference rounds and keep per-config minima: a shared/noisy
        # host drifts ±30% between runs, which biased BENCH_r02 — alternating rounds
        # in one process exposes both sides to the same drift
        for _ in range(2):
            _min_merge(out, {
                "stateful": _safe(bench_acc_stateful, preds, target),
                "ref_stateful": _safe(ref_acc_stateful),
                "scan": _safe(bench_acc_scan, preds, target),
                "curve": _safe(bench_pr_curve),
                "ref_curve": _safe(ref_pr_curve),
            })
        _min_merge(out, {"inception": _safe(bench_inception, "cpu-fallback")})
    elif mode == "mesh":
        force_cpu(8)
        _safe(_reference_modules)
        for _ in range(2):
            _min_merge(out, {
                "collection": _safe(bench_collection_mesh_sync),
                "ref_collection": _safe(ref_collection),
            })
    print(json.dumps(out))


def _run_fallback_via_workers() -> dict:
    """Run the config suite split across 1-device and 8-device CPU subprocesses."""
    merged: dict = {}
    for mode in ("single", "mesh"):
        try:
            proc = subprocess.run(
                [sys.executable, __file__, "--worker", mode],
                capture_output=True, text=True, timeout=1200,
            )
            if proc.returncode == 0 and proc.stdout.strip():
                merged.update(json.loads(proc.stdout.strip().splitlines()[-1]))
            else:
                sys.stderr.write(f"bench worker {mode} rc={proc.returncode}: {proc.stderr[-500:]}\n")
        except Exception as err:
            sys.stderr.write(f"bench worker {mode} failed: {err!r}\n")
    return merged


def main() -> None:
    hardware = _acquire_backend()
    if hardware == "cpu-fallback":
        ours = _run_fallback_via_workers()
        # reference numbers come interleaved from the same worker processes
        ref_stateful = ours.get("ref_stateful")
        ref_col = ours.get("ref_collection")
        ref_curve = ours.get("ref_curve")
    else:
        ours = _run_ours(hardware)
        _safe(_reference_modules)
        ref_stateful = _safe(ref_acc_stateful)
        ref_col = _safe(ref_collection)
        ref_curve = _safe(ref_pr_curve)
    ours_stateful = ours.get("stateful")
    ours_scan = ours.get("scan")
    ours_collection = ours.get("collection")
    ours_curve = ours.get("curve")
    ours_incep = ours.get("inception")

    def ratio(ref, ours):
        if ref is None or ours is None or ours <= 0:
            return None
        return round(ref / ours, 3)

    configs = {
        "acc_update_stateful": {
            "value": ours_stateful, "unit": "us/step", "baseline": ref_stateful,
            "vs_baseline": ratio(ref_stateful, ours_stateful),
        },
        "acc_update_scan": {
            "value": ours_scan, "unit": "us/step", "baseline": ref_stateful,
            "vs_baseline": ratio(ref_stateful, ours_scan),
        },
        "collection_acc_f1_auroc_mesh_sync": {
            "value": ours_collection, "unit": "us/step", "baseline": ref_col,
            "vs_baseline": ratio(ref_col, ours_collection),
            "note": "ours includes mesh sync every step; reference baseline is eager update+compute without any DDP sync",
        },
        "pr_curve_binned_50x4096": {
            "value": ours_curve, "unit": "ms/epoch", "baseline": ref_curve,
            "vs_baseline": ratio(ref_curve, ours_curve),
        },
        "inception_v3_features": {
            "value": ours_incep, "unit": "imgs/sec", "baseline": None, "vs_baseline": None,
            "note": "reference needs torch-fidelity weights (not installed); FLOPs-identical random-weight net",
        },
    }
    for cfg in configs.values():
        if isinstance(cfg.get("value"), float):
            cfg["value"] = round(cfg["value"], 2)
        if isinstance(cfg.get("baseline"), float):
            cfg["baseline"] = round(cfg["baseline"], 2)

    result = {
        "metric": f"MulticlassAccuracy per-step update+compute (4096x100, {STEPS} steps)",
        "value": round(ours_stateful, 2) if ours_stateful else None,
        "unit": "us/step",
        "vs_baseline": ratio(ref_stateful, ours_stateful),
        "hardware": hardware,
        "configs": configs,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--worker":
        _worker_main(sys.argv[2])
    else:
        main()
