"""Force JAX onto a virtual multi-device CPU mesh, despite the pinned TPU plugin.

The host image pins ``JAX_PLATFORMS=axon`` (a tunneled TPU PJRT plugin) via
sitecustomize; when the tunnel is wedged, backend init hangs forever. Tests, the
multichip dryrun, and the bench CPU fallback all need the same recipe: set the env
vars before JAX initialises, force the config, and deregister the axon factory so
nothing can touch the tunnel. Shared here so the recipe lives in exactly one place
(used by ``tests/conftest.py``, ``__graft_entry__.py``, ``bench.py``).
"""

from __future__ import annotations

import os


def force_cpu(n_devices: int = 8) -> None:
    """Pin this process to an ``n_devices`` virtual CPU mesh.

    Must be called before the JAX backend initialises to take full effect; callers
    that may run after init should verify ``len(jax.devices())`` themselves.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    xla_flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in xla_flags:
        os.environ["XLA_FLAGS"] = (
            xla_flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        import jax._src.xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass
